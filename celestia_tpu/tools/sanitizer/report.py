"""celestia-san report: T001-T005 synthesis from a finished Session.

The runtime half (runtime.py) records raw events keyed by lock
*creation sites*; this module resolves those sites to the same
"module.attr" tokens celestia-lint uses (by parsing the creating file
with `ast` — never importing it), evaluates the T-rules against the
declared partial order in specs/serving.md, and emits findings in
celestia-lint's exact `Finding` shape so the waiver and baseline
channels apply unchanged.

Fingerprints are deterministic by construction: T001/T002/T004 anchor
to lock CREATION sites (stable code locations), not to whichever racing
thread happened to observe the edge first; the observing call site is
carried in the message as information only. One seed run twice
therefore yields the identical finding set — `make san` gates on that.

  T001  observed cycle in the acquisition graph, or an observed edge
        running against the declared partial order
  T002  lock actually held across a device transfer / faults.fire
  T003  Condition.wait exercised at a call site outside a `while`
        predicate loop (runtime twin of C004; wait_for is exempt)
  T004  observed edge with an endpoint the declared order never ranks —
        the spec must be complete, not merely uncontradicted
  T005  declared lock that was instantiated during the run but never
        acquired (contract-coverage drift); declared locks never even
        instantiated (e.g. node._lock in a crypto-free run) are listed
        as `uncovered_tokens`, not findings
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

from celestia_tpu.tools.analysis import concurrency
from celestia_tpu.tools.analysis.core import (
    Finding, _short_name, apply_baseline, apply_waivers, collect_waivers,
    enclosing_symbol, load_baseline, load_project,
)
from celestia_tpu.tools.sanitizer.runtime import Session, Site

_OBS = "<observed>"


@dataclasses.dataclass
class SanReport:
    all_findings: list[Finding]
    new_findings: list[Finding]
    waived: int
    baselined: int
    edges: list[dict]                 # observed token edges
    tokens: dict[str, dict]           # token -> acquire/hold stats
    uncovered_tokens: list[str]       # declared, never instantiated
    probes_entered: list[str]

    def to_dict(self) -> dict:
        by_rule: dict[str, int] = {}
        for f in self.new_findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {
            "schema": "celestia-san/1",
            "total_findings": len(self.all_findings),
            "new_findings": [f.to_dict() for f in self.new_findings],
            "new_by_rule": dict(sorted(by_rule.items())),
            "waived": self.waived,
            "baselined": self.baselined,
            "edges": self.edges,
            "tokens": self.tokens,
            "uncovered_tokens": self.uncovered_tokens,
            "probes_entered": sorted(self.probes_entered),
        }

    def fingerprints(self) -> set[tuple]:
        return {f.fingerprint() for f in self.new_findings}


class _FileIndex:
    """Lazy per-file AST cache for token resolution and T003 checks."""

    def __init__(self, root: pathlib.Path):
        self.root = root.resolve()
        self._cache: dict[str, tuple] = {}

    def _load(self, filename: str):
        entry = self._cache.get(filename)
        if entry is not None:
            return entry
        path = pathlib.Path(filename)
        try:
            rel = path.resolve().relative_to(self.root).as_posix()
        except (ValueError, OSError):
            rel = path.name
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"),
                             filename=rel)
        except (OSError, SyntaxError, UnicodeDecodeError):
            tree = None
        entry = (rel, tree)
        self._cache[filename] = entry
        return entry

    def relpath(self, filename: str) -> str:
        return self._load(filename)[0]

    def resolve_token(self, site: Site) -> tuple[str, str, str]:
        """-> (token, symbol, relpath) for a lock creation site."""
        if site.token is not None:
            mod = site.token.split(".", 1)[0]
            return site.token, "<adopted>", f"celestia_tpu/{mod}.py"
        rel, tree = self._load(site.file)
        short = (_short_name(rel) if rel.endswith(".py")
                 else pathlib.Path(rel).stem)
        attr = None
        symbol = "<module>"
        if tree is not None:
            for node in ast.walk(tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if node.lineno != site.line:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        attr = tgt.attr
                    elif isinstance(tgt, ast.Name):
                        attr = tgt.id
                if attr is not None:
                    symbol = enclosing_symbol(tree, node)
                    break
        if attr is None:
            attr = f"<line{site.line}>"
        return f"{short}.{attr}", symbol, rel

    def in_while(self, filename: str, line: int) -> bool:
        _rel, tree = self._load(filename)
        if tree is None:
            return True  # unparseable: give the benefit of the doubt
        for node in ast.walk(tree):
            if isinstance(node, ast.While):
                end = getattr(node, "end_lineno", node.lineno)
                if node.lineno <= line <= end:
                    return True
        return False

    def symbol_at(self, filename: str, line: int) -> str:
        _rel, tree = self._load(filename)
        if tree is None:
            return "<module>"
        target = None
        for node in ast.walk(tree):
            if getattr(node, "lineno", None) == line:
                target = node
                break
        if target is None:
            return "<module>"
        return enclosing_symbol(tree, target)


def _spec_order_line(project) -> int:
    text = project.spec_files.get("specs/serving.md", "")
    in_section = False
    for i, line in enumerate(text.splitlines(), start=1):
        if line.startswith("#"):
            in_section = "lock ordering" in line.lower()
            continue
        if in_section and ("→" in line or "->" in line):
            return i
    return 1


def _sccs(graph: dict[str, set[str]]) -> list[list[str]]:
    """Iterative Tarjan; returns SCCs with more than one node."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[list[str]] = []
    counter = [0]

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    n = stack.pop()
                    on_stack.discard(n)
                    scc.append(n)
                    if n == node:
                        break
                if len(scc) > 1:
                    out.append(sorted(scc))
    return out


def finalize(session: Session, root: pathlib.Path | str,
             ranks: dict[str, int] | None = None,
             coverage: bool = True,
             baseline_path: pathlib.Path | str | None = None,
             apply_suppressions: bool = True) -> SanReport:
    """Resolve a finished (deactivated) session into a SanReport.

    `ranks` overrides the declared order (tests); `coverage=False`
    skips T005 (partial runs — the pytest --san arm, bench arms).
    """
    root = pathlib.Path(root)
    project = load_project(root)
    if ranks is None:
        ranks = concurrency.declared_order(project)
    files = _FileIndex(root)

    tokens: dict[int, tuple[str, str, str]] = {}  # sid -> (tok, sym, rel)
    for sid, site in session.owned_sites.items():
        tokens[sid] = files.resolve_token(site)

    def tok(sid: int) -> str | None:
        entry = tokens.get(sid)
        return entry[0] if entry else None

    findings: list[Finding] = []
    spec_line = _spec_order_line(project)

    # -- token-level edge map (first obs per token pair, deterministic
    #    anchor = inner lock's creation site) --------------------------
    token_edges: dict[tuple[str, str], dict] = {}
    for (o_sid, i_sid), obs in session.edges.items():
        a, b = tok(o_sid), tok(i_sid)
        if a is None or b is None or a == b:
            continue
        e = token_edges.get((a, b))
        if e is None:
            inner_site = session.owned_sites[i_sid]
            _t, _sym, inner_rel = tokens[i_sid]
            token_edges[(a, b)] = {
                "outer": a, "inner": b, "count": obs["count"],
                "path": inner_rel, "line": inner_site.line,
                "obs_file": files.relpath(obs["file"]),
                "obs_line": obs["line"],
            }
        else:
            e["count"] += obs["count"]

    # -- T001: cycles + declared-order violations ----------------------
    graph: dict[str, set[str]] = {}
    for (a, b) in token_edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    for scc in _sccs(graph):
        match = ("<->".join(scc) if len(scc) == 2
                 else "cycle:" + "->".join(scc))
        in_scc = [e for (a, b), e in sorted(token_edges.items())
                  if a in scc and b in scc]
        anchor = in_scc[0]
        obs = ", ".join(f"{e['outer']}->{e['inner']} at "
                        f"{e['obs_file']}:{e['obs_line']}"
                        for e in in_scc)
        findings.append(Finding(
            rule="T001", path=anchor["path"], line=anchor["line"],
            symbol=_OBS, match=match,
            message=f"observed lock-order cycle {' / '.join(scc)} "
                    f"({obs}) — deadlock seed",
        ))
    for (a, b), e in sorted(token_edges.items()):
        ra, rb = ranks.get(a), ranks.get(b)
        if ra is not None and rb is not None and ra > rb:
            findings.append(Finding(
                rule="T001", path=e["path"], line=e["line"],
                symbol=_OBS, match=f"{a}->{b}",
                message=f"observed acquisition {a} -> {b} "
                        f"({e['count']}x, first at {e['obs_file']}:"
                        f"{e['obs_line']}) runs against the declared "
                        "partial order in specs/serving.md",
            ))
        # -- T004: endpoint the spec never ranks -----------------------
        elif ra is None or rb is None:
            missing = sorted(t for t, r in ((a, ra), (b, rb))
                             if r is None)
            findings.append(Finding(
                rule="T004", path=e["path"], line=e["line"],
                symbol=_OBS, match=f"{a}->{b}",
                message=f"observed acquisition edge {a} -> {b} "
                        f"({e['count']}x, first at {e['obs_file']}:"
                        f"{e['obs_line']}) has undeclared endpoint(s) "
                        f"{', '.join(missing)} — extend the "
                        "specs/serving.md lock ordering",
            ))

    # -- T002: lock held across a probe --------------------------------
    t002_seen: dict[tuple[str, str], dict] = {}
    for (sid, tail), obs in session.t002.items():
        t = tok(sid)
        if t is None:
            continue
        key = (t, tail)
        e = t002_seen.get(key)
        if e is None:
            site = session.owned_sites[sid]
            _t, _sym, rel = tokens[sid]
            t002_seen[key] = {
                "path": rel, "line": site.line, "count": obs["count"],
                "obs_file": files.relpath(obs["file"]),
                "obs_line": obs["line"],
            }
        else:
            e["count"] += obs["count"]
    for (t, tail), e in sorted(t002_seen.items()):
        what = ("faults.fire" if tail == "fire"
                else f"transfers.{tail}")
        findings.append(Finding(
            rule="T002", path=e["path"], line=e["line"], symbol=_OBS,
            match=f"{t}:{tail}",
            message=f"{t} held across {what}() ({e['count']}x, first "
                    f"at {e['obs_file']}:{e['obs_line']}) — injected "
                    "delay or DMA latency convoys every waiter",
        ))

    # -- T003: wait exercised outside a while loop ---------------------
    for (file, line), sid in sorted(session.wait_sites.items()):
        if files.in_while(file, line):
            continue
        t = tok(sid) or "<cond>"
        findings.append(Finding(
            rule="T003", path=files.relpath(file), line=line,
            symbol=files.symbol_at(file, line), match=t,
            message=f"{t}.wait() returned at a call site outside a "
                    "while predicate loop — spurious wakeup / lost "
                    "notify hazard observed at runtime",
        ))

    # -- T005 + instantiation coverage ---------------------------------
    token_stats: dict[str, dict] = {}
    for sid, (t, _sym, _rel) in sorted(tokens.items(),
                                       key=lambda kv: kv[1][0]):
        st = token_stats.setdefault(
            t, {"acquires": 0, "holds": 0, "hold_total_s": 0.0,
                "hold_max_s": 0.0})
        st["acquires"] += session.acquires.get(sid, 0)
        h = session.holds.get(sid)
        if h:
            st["holds"] += h[0]
            st["hold_total_s"] = round(st["hold_total_s"] + h[1], 6)
            st["hold_max_s"] = round(max(st["hold_max_s"], h[2]), 6)

    instantiated = set(token_stats)
    acquired = {t for t, st in token_stats.items() if st["acquires"]}
    uncovered = sorted(set(ranks) - instantiated)
    if coverage:
        for t in sorted(set(ranks) & instantiated - acquired):
            findings.append(Finding(
                rule="T005", path="specs/serving.md", line=spec_line,
                symbol="<lock-ordering>", match=t,
                message=f"declared lock {t} was instantiated but never "
                        "acquired during the sanitized run — the "
                        "declared order drifted from exercised "
                        "behaviour (extend the hammer or prune the "
                        "spec)",
            ))

    findings.sort(key=lambda f: (f.rule, f.path, f.line, f.match))

    # -- suppression channels (celestia-lint protocol, unchanged) ------
    waived = baselined = 0
    new = findings
    if apply_suppressions:
        waivers = []
        for mod in project.modules:
            ws, _bad = collect_waivers(mod)
            waivers.extend(ws)
        after = apply_waivers(findings, waivers)
        entries = []
        if baseline_path is None:
            baseline_path = root / "config" / "lint_baseline.json"
        bp = pathlib.Path(baseline_path)
        if bp.exists():
            entries = load_baseline(bp)
        new = apply_baseline(after, entries)
        waived = len(findings) - len(after)
        baselined = len(after) - len(new)

    edge_list = [dict(e) for _k, e in sorted(token_edges.items())]
    return SanReport(
        all_findings=findings, new_findings=new,
        waived=waived, baselined=baselined,
        edges=edge_list, tokens=token_stats,
        uncovered_tokens=uncovered,
        probes_entered=sorted(session.probes_entered),
    )
